"""TURN client (webrtc/turn.py) against an in-test RFC 5766 server
subset, and the NAT'd-server story end-to-end: RTCPeer media flowing
while the browser simulator talks ONLY to the relayed address (host
candidate unreachable — VERDICT r3 missing #2 'done' bar)."""

import asyncio
import hashlib
import struct

import numpy as np
import pytest

# the loopback tests drive RTCPeer, whose DTLS layer binds OpenSSL at
# import time; skip cleanly where the DTLS-SRTP surface is missing
pytest.importorskip("selkies_tpu.webrtc.dtls",
                    reason="usable OpenSSL (DTLS-SRTP surface) required",
                    exc_type=ImportError)

from selkies_tpu.webrtc import turn as T
from selkies_tpu.webrtc.stun import StunMessage, make_ice_credentials

REALM = "selkies-test"
USER = "u1"
PASSWORD = "pw1"
NONCE = b"nonce-1"


class MiniTurnServer(asyncio.DatagramProtocol):
    """Just enough RFC 5766: long-term-credential Allocate (401 dance),
    Refresh, CreatePermission, ChannelBind, Send/Data indications and
    ChannelData relaying, one allocation per 5-tuple."""

    def __init__(self):
        self.transport = None
        self.allocs = {}            # client_addr -> _Alloc
        self.auth_failures = 0

    def connection_made(self, transport):
        self.transport = transport

    def datagram_received(self, data, addr):
        asyncio.ensure_future(self._handle(data, addr))

    async def _handle(self, data, addr):
        alloc = self.allocs.get(addr)
        if T.is_channel_data(data):
            if alloc is None:
                return
            ch, ln = struct.unpack_from("!HH", data, 0)
            peer = alloc["channels"].get(ch)
            if peer is not None:
                alloc["relay_t"].sendto(data[4:4 + ln], peer)
            return
        msg = StunMessage.parse(data)
        method = msg.type
        if method == T.M_SEND_IND:
            if alloc is None:
                return
            peer = T.unxor_address(msg.attr(T.ATTR_XOR_PEER_ADDRESS))
            payload = msg.attr(T.ATTR_DATA)
            if peer and payload is not None \
                    and peer[0] in alloc["perms"]:
                alloc["relay_t"].sendto(payload, peer)
            return
        # requests need auth
        key = hashlib.md5(
            f"{USER}:{REALM}:{PASSWORD}".encode()).digest()
        if msg.attr(T.ATTR_USERNAME) is None \
                or not msg.check_integrity(key):
            self.auth_failures += 1
            err = StunMessage(method | 0x0110, msg.txid)
            err.add(T.ATTR_ERROR_CODE, b"\x00\x00\x04\x01Unauthorized")
            err.add(T.ATTR_REALM, REALM.encode())
            err.add(T.ATTR_NONCE, NONCE)
            self.transport.sendto(err.to_bytes(), addr)
            return
        resp = StunMessage(method | 0x0100, msg.txid)
        if method == T.M_ALLOCATE:
            if alloc is None:
                alloc = {"perms": set(), "channels": {}, "chan_rev": {}}
                loop = asyncio.get_running_loop()

                server = self

                class _Relay(asyncio.DatagramProtocol):
                    def connection_made(self, t):
                        alloc["relay_t"] = t

                    def datagram_received(self, d, peer):
                        server._from_peer(addr, d, peer)

                await loop.create_datagram_endpoint(
                    _Relay, local_addr=("127.0.0.1", 0))
                alloc["relay_addr"] = \
                    alloc["relay_t"].get_extra_info("sockname")[:2]
                self.allocs[addr] = alloc
            resp.add(T.ATTR_XOR_RELAYED_ADDRESS,
                     T.xor_address(*alloc["relay_addr"]))
            resp.add(T.ATTR_LIFETIME, struct.pack("!I", 600))
        elif method == T.M_REFRESH:
            resp.add(T.ATTR_LIFETIME, struct.pack("!I", 600))
        elif method == T.M_CREATE_PERMISSION:
            peer = T.unxor_address(msg.attr(T.ATTR_XOR_PEER_ADDRESS))
            alloc["perms"].add(peer[0])
        elif method == T.M_CHANNEL_BIND:
            ch = struct.unpack_from(
                "!H", msg.attr(T.ATTR_CHANNEL_NUMBER), 0)[0]
            peer = T.unxor_address(msg.attr(T.ATTR_XOR_PEER_ADDRESS))
            alloc["channels"][ch] = peer
            alloc["chan_rev"][peer] = ch
            alloc["perms"].add(peer[0])
        # success responses to authed requests are integrity-protected
        # (RFC 5389 §10.2.3) — the client now REQUIRES this once it
        # knows the realm (ADVICE r5 satellite)
        self.transport.sendto(resp.to_bytes(integrity_key=key), addr)

    def _from_peer(self, client_addr, data, peer):
        alloc = self.allocs.get(client_addr)
        if alloc is None or peer[0] not in alloc["perms"]:
            return                        # no permission: drop (RFC 5766)
        ch = alloc["chan_rev"].get(peer)
        if ch is not None:
            frame = struct.pack("!HH", ch, len(data)) + data
            frame += b"\x00" * (-len(data) % 4)
            self.transport.sendto(frame, client_addr)
        else:
            ind = StunMessage(T.M_DATA_IND)
            ind.add(T.ATTR_XOR_PEER_ADDRESS, T.xor_address(*peer))
            ind.add(T.ATTR_DATA, data)
            self.transport.sendto(ind.to_bytes(), client_addr)


class _PeerSock(asyncio.DatagramProtocol):
    def __init__(self):
        self.queue = asyncio.Queue()
        self.transport = None

    def connection_made(self, t):
        self.transport = t

    def datagram_received(self, data, addr):
        self.queue.put_nowait((data, addr))


async def _start_server():
    loop = asyncio.get_running_loop()
    srv = MiniTurnServer()
    t, _ = await loop.create_datagram_endpoint(
        lambda: srv, local_addr=("127.0.0.1", 0))
    return srv, t.get_extra_info("sockname")[:2]


async def test_allocate_permission_send_and_channel_data():
    srv, saddr = await _start_server()
    got = asyncio.Queue()
    cli = T.TurnClient(saddr, USER, PASSWORD,
                       on_data=lambda d, p: got.put_nowait((d, p)))
    await cli.connect()
    relayed = await cli.allocate()
    assert srv.auth_failures == 1          # exactly one 401 dance
    assert relayed[0] == "127.0.0.1"

    loop = asyncio.get_running_loop()
    peer = _PeerSock()
    await loop.create_datagram_endpoint(
        lambda: peer, local_addr=("127.0.0.1", 0))
    peer_addr = peer.transport.get_extra_info("sockname")[:2]

    # without a permission the peer's datagram is dropped
    peer.transport.sendto(b"early", relayed)
    await asyncio.sleep(0.1)
    assert got.empty()

    await cli.create_permission(peer_addr[0])
    peer.transport.sendto(b"hello-relay", relayed)
    data, frm = await asyncio.wait_for(got.get(), 2)
    assert data == b"hello-relay" and frm == peer_addr

    # client -> peer rides a Send indication pre-bind
    cli.send_to_peer(b"reply-1", peer_addr)
    data, frm = await asyncio.wait_for(peer.queue.get(), 2)
    assert data == b"reply-1" and frm == relayed

    # channel bind upgrades both directions to 4-byte framing
    ch = await cli.channel_bind(peer_addr)
    assert 0x4000 <= ch <= 0x7FFF
    cli.send_to_peer(b"reply-2", peer_addr)
    data, frm = await asyncio.wait_for(peer.queue.get(), 2)
    assert data == b"reply-2"
    peer.transport.sendto(b"via-channel", relayed)
    data, frm = await asyncio.wait_for(got.get(), 2)
    assert data == b"via-channel" and frm == peer_addr

    await cli.refresh()
    cli.close()


async def test_wrong_password_fails_cleanly():
    srv, saddr = await _start_server()
    cli = T.TurnClient(saddr, USER, "wrong", on_data=None)
    await cli.connect()
    with pytest.raises(T.TurnError):
        await cli.allocate()
    cli.close()


async def test_media_flows_with_host_candidate_firewalled():
    """The VERDICT 'done' bar: an RTC session establishes and streams
    REAL media with the browser talking ONLY to the relayed address —
    never to the peer's host candidate."""
    from selkies_tpu.codecs import h264_ref_decoder as refdec
    from selkies_tpu.webrtc.dtls import DtlsEndpoint
    from selkies_tpu.webrtc.peer import RTCPeer
    from selkies_tpu.webrtc.rtp import RtpPacket
    from selkies_tpu.webrtc.sdp import build_offer, parse_answer
    from selkies_tpu.webrtc.srtp import SrtpContext
    from selkies_tpu.webrtc.stun import IceLiteResponder, is_stun
    from tests.test_webrtc_media import (_small_idr, depacketize_h264)

    srv, saddr = await _start_server()
    peer = RTCPeer(turn_config={
        "host": saddr[0], "port": saddr[1],
        "username": USER, "password": PASSWORD})
    await peer.listen()
    assert peer.relay_addr is not None
    offer = peer.create_offer()
    assert "typ relay" in offer

    # browser side: socket pointed at the RELAYED address only
    remote = parse_answer(offer)
    cli_ice = IceLiteResponder(*make_ice_credentials())
    cli_ice.set_remote(remote.ice_ufrag, remote.ice_pwd)
    answer = build_offer("127.0.0.1", 0, cli_ice.ufrag, cli_ice.pwd,
                         remote.fingerprint).replace(
        "a=setup:actpass", "a=setup:active")
    peer.set_remote_answer(answer)       # installs the 127.0.0.1 permission
    await asyncio.sleep(0.2)

    browser = _PeerSock()
    loop = asyncio.get_running_loop()
    await loop.create_datagram_endpoint(
        lambda: browser, remote_addr=peer.relay_addr)

    async def recv(timeout=2.0):
        d, _ = await asyncio.wait_for(browser.queue.get(), timeout)
        return d

    browser.transport.sendto(cli_ice.binding_request())
    resp = await recv()
    assert is_stun(resp)

    cli_dtls = DtlsEndpoint(server=False)
    cli_dtls.handshake()
    browser.transport.sendto(cli_dtls.take_outgoing())
    for _ in range(12):
        if cli_dtls.handshake_complete and peer.srtp is not None:
            break
        try:
            d = await recv()
        except asyncio.TimeoutError:
            d = b""
        if d and 20 <= d[0] <= 63:
            cli_dtls.feed(d)
            out = cli_dtls.take_outgoing()
            if out:
                browser.transport.sendto(out)
    assert cli_dtls.handshake_complete
    await asyncio.wait_for(peer.connected.wait(), 2)

    ck, sk = cli_dtls.export_srtp_keys()
    cli_srtp = SrtpContext(ck, sk, is_client=True)
    annexb, enc = _small_idr()
    assert peer.send_video_au(annexb) > 0

    rtp_pkts = []
    deadline = asyncio.get_running_loop().time() + 3
    while asyncio.get_running_loop().time() < deadline:
        try:
            d = await recv(0.3)
        except asyncio.TimeoutError:
            break
        if d and 128 <= d[0] <= 191:
            pt = d[1] & 0x7F
            if 64 <= pt <= 95:
                cli_srtp.unprotect_rtcp(d)
            else:
                rtp_pkts.append(RtpPacket.parse(cli_srtp.unprotect_rtp(d)))
    assert rtp_pkts, "no media arrived over the relay"
    my, mu, mv = refdec.Decoder().decode(depacketize_h264(rtp_pkts))
    assert np.array_equal(my, enc.recon_y)
    assert np.array_equal(mu, enc.recon_u)
    assert np.array_equal(mv, enc.recon_v)
    peer.close()


# ---------------------------------------------------------------- MI gating
def _mi_client():
    """TurnClient with realm/nonce learned, plus a pending request whose
    future exposes whether a response was accepted."""
    cli = T.TurnClient(("127.0.0.1", 1), USER, PASSWORD)
    cli.realm = REALM
    cli.nonce = NONCE
    req = StunMessage(T.M_ALLOCATE)
    fut = asyncio.get_running_loop().create_future()
    cli._pending[req.txid] = fut
    return cli, req, fut


def _lt_key():
    return hashlib.md5(f"{USER}:{REALM}:{PASSWORD}".encode()).digest()


async def test_mi_less_success_response_dropped():
    """Satellite (ADVICE r5): once the realm is known, a success
    response WITHOUT MESSAGE-INTEGRITY must be dropped — an off-path
    attacker who observed the txid could otherwise inject a bogus
    relayed address."""
    cli, req, fut = _mi_client()
    forged = StunMessage(T.M_ALLOCATE | 0x0100, req.txid)
    forged.add(T.ATTR_XOR_RELAYED_ADDRESS, T.xor_address("6.6.6.6", 666))
    cli._on_datagram(forged.to_bytes())
    assert not fut.done(), "unsigned success must not resolve the request"
    # the genuine, signed response still lands afterwards
    real = StunMessage(T.M_ALLOCATE | 0x0100, req.txid)
    real.add(T.ATTR_XOR_RELAYED_ADDRESS, T.xor_address("127.0.0.1", 5))
    cli._on_datagram(real.to_bytes(integrity_key=_lt_key()))
    assert fut.done()


async def test_mi_bad_signature_dropped():
    cli, req, fut = _mi_client()
    forged = StunMessage(T.M_ALLOCATE | 0x0100, req.txid)
    cli._on_datagram(forged.to_bytes(integrity_key=b"\x00" * 16))
    assert not fut.done()


async def test_mi_less_reauth_errors_still_accepted():
    """401/438 are sent BEFORE auth to (re)issue realm/nonce — they
    legitimately lack MI and must keep working or nonce refresh dies."""
    for code_bytes in (b"\x00\x00\x04\x01Unauthorized",
                       b"\x00\x00\x04\x26Stale"):
        cli, req, fut = _mi_client()
        err = StunMessage(T.M_ALLOCATE | 0x0110, req.txid)
        err.add(T.ATTR_ERROR_CODE, code_bytes)
        err.add(T.ATTR_REALM, REALM.encode())
        err.add(T.ATTR_NONCE, b"nonce-2")
        cli._on_datagram(err.to_bytes())
        assert fut.done(), code_bytes


async def test_mi_less_other_error_dropped():
    cli, req, fut = _mi_client()
    err = StunMessage(T.M_ALLOCATE | 0x0110, req.txid)
    err.add(T.ATTR_ERROR_CODE, b"\x00\x00\x04\x03Forbidden")
    cli._on_datagram(err.to_bytes())
    assert not fut.done()


async def test_mi_not_required_before_realm_known():
    """The FIRST 401 arrives before any credentials exist — requiring MI
    there would deadlock the auth dance."""
    cli = T.TurnClient(("127.0.0.1", 1), USER, PASSWORD)
    req = StunMessage(T.M_ALLOCATE)
    fut = asyncio.get_running_loop().create_future()
    cli._pending[req.txid] = fut
    err = StunMessage(T.M_ALLOCATE | 0x0110, req.txid)
    err.add(T.ATTR_ERROR_CODE, b"\x00\x00\x04\x01U")
    err.add(T.ATTR_REALM, REALM.encode())
    err.add(T.ATTR_NONCE, NONCE)
    cli._on_datagram(err.to_bytes())
    assert fut.done()
