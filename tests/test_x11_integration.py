"""Real-X11 integration: capture -> encode -> WS -> decode, plus XTEST
injection verified through XQueryPointer (VERDICT round-2 item 8; the
reference's grungiest surface, SURVEY §7 hard-part 5).

Needs an Xvfb binary — present in the example container (Dockerfile),
absent from the bare CI image, so everything here skips gracefully.
Run inside the container with: ``pytest -m x11``.
"""

import asyncio
import ctypes
import ctypes.util
import os
import shutil
import socket
import subprocess
import time

import numpy as np
import pytest

pytestmark = [
    pytest.mark.x11,
    pytest.mark.skipif(shutil.which("Xvfb") is None,
                       reason="Xvfb not installed (run in the container)"),
]

DISPLAY = ":99"
W, H = 640, 480


@pytest.fixture(scope="module")
def xvfb():
    proc = subprocess.Popen(
        ["Xvfb", DISPLAY, "-screen", "0", f"{W}x{H}x24", "-nolisten", "tcp"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    sock = f"/tmp/.X11-unix/X{DISPLAY[1:]}"
    deadline = time.time() + 10
    while time.time() < deadline and not os.path.exists(sock):
        time.sleep(0.1)
    if not os.path.exists(sock):
        proc.terminate()
        pytest.skip("Xvfb failed to start")
    yield DISPLAY
    proc.terminate()
    proc.wait(timeout=5)


class _X:
    """Tiny ctypes X helper for fixture drawing + pointer queries."""

    def __init__(self, display):
        self.lib = ctypes.CDLL(ctypes.util.find_library("X11"))
        self.lib.XOpenDisplay.restype = ctypes.c_void_p
        self.lib.XDefaultRootWindow.restype = ctypes.c_ulong
        self.lib.XCreateGC.restype = ctypes.c_void_p
        self.dpy = self.lib.XOpenDisplay(display.encode())
        assert self.dpy, f"cannot open {display}"
        self.root = self.lib.XDefaultRootWindow(ctypes.c_void_p(self.dpy))

    def fill_rect(self, x, y, w, h, rgb):
        gc = self.lib.XCreateGC(ctypes.c_void_p(self.dpy),
                                ctypes.c_ulong(self.root), 0, None)
        self.lib.XSetForeground(ctypes.c_void_p(self.dpy),
                                ctypes.c_void_p(gc), ctypes.c_ulong(rgb))
        self.lib.XFillRectangle(ctypes.c_void_p(self.dpy),
                                ctypes.c_ulong(self.root),
                                ctypes.c_void_p(gc), x, y, w, h)
        self.lib.XSync(ctypes.c_void_p(self.dpy), 0)
        self.lib.XFreeGC(ctypes.c_void_p(self.dpy), ctypes.c_void_p(gc))

    def pointer_xy(self):
        root = ctypes.c_ulong()
        child = ctypes.c_ulong()
        rx, ry, wx, wy = (ctypes.c_int() for _ in range(4))
        mask = ctypes.c_uint()
        self.lib.XQueryPointer(
            ctypes.c_void_p(self.dpy), ctypes.c_ulong(self.root),
            ctypes.byref(root), ctypes.byref(child),
            ctypes.byref(rx), ctypes.byref(ry),
            ctypes.byref(wx), ctypes.byref(wy), ctypes.byref(mask))
        return rx.value, ry.value


def test_x11_capture_sees_drawn_content(xvfb):
    from selkies_tpu.engine.sources import X11Source

    x = _X(xvfb)
    x.fill_rect(0, 0, W, H, 0x202020)
    x.fill_rect(100, 100, 200, 150, 0xFF4000)
    src = X11Source(display=xvfb)
    frame = np.asarray(src.get_frame(0))
    assert frame.shape == (H, W, 3)
    inside = frame[150, 180]
    outside = frame[50, 500]
    assert inside[0] > 180 and int(outside[0]) < 80, (inside, outside)


def test_xtest_injection_moves_pointer(xvfb):
    from selkies_tpu.input.backends import X11Backend

    x = _X(xvfb)
    be = X11Backend(display=xvfb)
    be.pointer_motion(123, 77)
    time.sleep(0.1)
    assert x.pointer_xy() == (123, 77)
    be.pointer_motion(400, 300)
    time.sleep(0.1)
    assert x.pointer_xy() == (400, 300)


async def test_x11_ws_end_to_end(xvfb, client_factory):
    """Live Xvfb content through the full server: capture -> TPU encode
    -> WS 0x04 stripes -> spec-decoder, then a WS mouse verb lands in the
    X server."""
    from aiohttp import WSMsgType

    from selkies_tpu.codecs import h264_ref_decoder as refdec
    from selkies_tpu.input.backends import X11Backend
    from selkies_tpu.input.handler import InputHandler
    from selkies_tpu.server.core import CentralizedStreamServer
    from selkies_tpu.server.ws_service import WebSocketsService
    from selkies_tpu.settings import AppSettings

    x = _X(xvfb)
    x.fill_rect(0, 0, W, H, 0x3060A0)
    s = AppSettings.parse([], {})
    s.set_server("display_id", xvfb)
    s.set_server("encoder", "h264-tpu-striped")
    s.set_server("initial_width", W)
    s.set_server("initial_height", H)
    s.set_server("h264_motion_vrange", 2)
    s.set_server("h264_motion_hrange", 1)
    handler = InputHandler(backend=X11Backend(display=xvfb))
    svc = WebSocketsService(s, input_handler=handler)
    server = CentralizedStreamServer(s)
    server.register_service("websockets", svc)
    c = await client_factory(server)
    ws = await c.ws_connect("/api/websockets")
    while True:
        msg = await ws.receive(timeout=2)
        if msg.type != WSMsgType.TEXT or \
                msg.data.startswith("server_settings"):
            break
    await ws.send_str("START_VIDEO")
    streams = {}
    got_idr = False
    deadline = time.time() + 180          # first jit compile dominates
    while time.time() < deadline and not got_idr:
        try:
            msg = await ws.receive(timeout=5)
        except (asyncio.TimeoutError, TimeoutError):
            continue
        if msg.type != WSMsgType.BINARY or msg.data[0] != 0x04:
            continue
        import struct
        ftype, fid, y0, sw, sh = struct.unpack_from("!BHHHH", msg.data, 1)
        streams.setdefault(y0, []).append(msg.data[10:])
        await ws.send_str(f"CLIENT_FRAME_ACK {fid}")
        if ftype == 0x01:
            got_idr = True
    assert got_idr, "no IDR stripe arrived from the live X capture"
    y0 = sorted(streams)[0]
    y, _, _ = refdec.Decoder().decode(b"".join(streams[y0]))
    assert y.shape[1] >= W        # MB-padded width
    assert y.mean() > 16, "decoded stripe should carry the blue fill"

    await ws.send_str("m,222,111")
    await asyncio.sleep(0.3)
    assert x.pointer_xy() == (222, 111)
    await ws.close()


def test_spare_keycode_overlay_binds_unmapped_keysyms(xvfb):
    """A Unicode keysym the server layout lacks gets bound onto a spare
    keycode on first press (the reference's overlay binding,
    input_handler.py:760-932) and resolves afterwards."""
    from selkies_tpu.input.backends import X11Backend
    from selkies_tpu.input.keysyms import char_to_keysym

    be = X11Backend(display=xvfb)
    arrow = char_to_keysym("→")              # 0x01002192
    assert ctypes.CDLL(ctypes.util.find_library("X11")) is not None
    be.key(arrow, True)
    be.key(arrow, False)
    assert arrow in be._overlay, "spare keycode was not bound"
    code = be._x.XKeysymToKeycode(ctypes.c_void_p(be._dpy),
                                  ctypes.c_ulong(arrow))
    assert code == be._overlay[arrow]


def test_layout_matrix_us_de_fr(xvfb):
    """Layout matrix (VERDICT r3 next-9): align the X keymap with each
    layout the client detects (the same ``setxkbmap`` call
    ws_service._apply_keyboard_layout makes), then type layout-specific
    characters through the backend. Every keysym must land on a real
    keycode — natively when the layout carries it, via the spare-keycode
    overlay otherwise — so non-US layouts type correctly end-to-end
    (reference server_keysym_map.py + lib/keyboard-layout.js)."""
    if shutil.which("setxkbmap") is None:
        pytest.skip("setxkbmap not installed (run in the container)")
    from selkies_tpu.input.backends import X11Backend
    from selkies_tpu.input.keysyms import char_to_keysym

    probes = {
        "us": "az['#",
        "de": "äöüß",        # native on de, overlay-bound on others
        "fr": "éèçà",        # azerty accent row
    }
    env = dict(os.environ, DISPLAY=xvfb)
    try:
        for layout, chars in probes.items():
            r = subprocess.run(["setxkbmap", layout], env=env,
                               capture_output=True)
            if r.returncode != 0:
                pytest.skip(f"setxkbmap {layout} failed: "
                            f"{r.stderr.decode(errors='replace')}")
            be = X11Backend(display=xvfb)
            for ch in chars:
                ks = char_to_keysym(ch)
                be.key(ks, True)
                be.key(ks, False)
                code = be._x.XKeysymToKeycode(ctypes.c_void_p(be._dpy),
                                              ctypes.c_ulong(ks))
                assert code != 0, f"{layout}: {ch!r} has no keycode"
    finally:
        subprocess.run(["setxkbmap", "us"], env=env,
                       capture_output=True)


def test_clipboard_selection_owner_roundtrip(xvfb):
    """Two X clients: one takes the CLIPBOARD selection, the monitor
    notices and reads the text; then the reverse direction."""
    from selkies_tpu.input.clipboard_x11 import X11ClipboardMonitor

    seen = []
    server_side = X11ClipboardMonitor(xvfb, on_clipboard=seen.append)
    server_side.start()
    app_side = X11ClipboardMonitor(xvfb)
    app_side.start()
    try:
        app_side.set_clipboard("copied in a remote app")
        deadline = time.time() + 10
        while time.time() < deadline and not seen:
            time.sleep(0.1)
        assert seen == ["copied in a remote app"]

        got = []
        app_side.on_clipboard = got.append
        server_side.set_clipboard("pasted from the web client")
        deadline = time.time() + 10
        while time.time() < deadline and not got:
            time.sleep(0.1)
        assert got == ["pasted from the web client"]
    finally:
        server_side.stop()
        app_side.stop()
