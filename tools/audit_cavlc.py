"""CAVLC table audit: emit single-MB streams with CRAFTED coefficient
levels, decode with ffmpeg (ground truth), compare against our own
reconstruction. A mismatch/parse error pinpoints the exact table entry
(tc, t1, tz, runs, nC) that is wrong.

Run: env -u PALLAS_AXON_POOL_IPS python tools/audit_cavlc.py [--quick]
"""

from __future__ import annotations

import itertools
import sys

import numpy as np

sys.path.insert(0, ".")

from selkies_tpu.codecs import h264 as H                     # noqa: E402
from selkies_tpu.codecs.h264 import (BitWriter, LUMA_BLK_ORDER,   # noqa: E402
                                     _dequant4_ac, _dequant_chroma_dc,
                                     _dequant_luma_dc, _inv4,
                                     _write_residual_block, nal,
                                     slice_header_bits)
from selkies_tpu.codecs.h264_tables import QPC_NP, ZIGZAG4_NP  # noqa: E402
from selkies_tpu.native import avshim                         # noqa: E402

_H4 = np.array([[1, 1, 1, 1], [1, 1, -1, -1],
                [1, -1, -1, 1], [1, -1, 1, -1]], np.int64)
H2 = np.array([[1, 1], [1, -1]], np.int64)


def build_stream(qp, dc_lvl, ac_lvl, cdc_lvl, cac_lvl, n_mbs=1):
    """Craft an IDR with ``n_mbs`` MBs in one row, all using the SAME
    levels (so nC contexts grow across MBs), pred DC. Returns
    (annexb, expected_y, expected_u, expected_v)."""
    W = 16 * n_mbs
    qpc = int(QPC_NP[qp])
    bs = bytearray(H.write_sps(W, 16) + H.write_pps())
    w = BitWriter()
    slice_header_bits(w, 0, qp)
    exp_y = np.zeros((16, W), np.int64)
    exp_u = np.zeros((8, W // 2), np.int64)
    exp_v = np.zeros((8, W // 2), np.int64)
    nnz_y = np.zeros((n_mbs, 4, 4), np.int64)
    nnz_c = np.zeros((n_mbs, 2, 2, 2), np.int64)
    edge_y = None
    edge_c = None
    for k in range(n_mbs):
        cbp_luma = 15 if np.any(ac_lvl) else 0
        has_cac = bool(np.any(cac_lvl))
        has_cdc = bool(np.any(cdc_lvl))
        cbp_chroma = 2 if has_cac else (1 if has_cdc else 0)
        mb_type = 1 + 2 + 4 * cbp_chroma + (12 if cbp_luma else 0)
        w.ue(mb_type)
        w.ue(0)
        w.se(0)
        nc = H.I16Encoder._nc_luma(nnz_y, k, 0, 0)
        _write_residual_block(w, dc_lvl.reshape(16)[ZIGZAG4_NP], nc, 16)
        if cbp_luma:
            for br, bc in LUMA_BLK_ORDER:
                nc = H.I16Encoder._nc_luma(nnz_y, k, br, bc)
                tc = _write_residual_block(w, ac_lvl[br, bc][1:], nc, 15)
                nnz_y[k, br, bc] = tc
        if cbp_chroma:
            for ci in range(2):
                scan = np.array([cdc_lvl[ci, 0, 0], cdc_lvl[ci, 0, 1],
                                 cdc_lvl[ci, 1, 0], cdc_lvl[ci, 1, 1]])
                _write_residual_block(w, scan, -1, 4)
        if cbp_chroma == 2:
            for ci in range(2):
                for br in range(2):
                    for bc in range(2):
                        nc = H.I16Encoder._nc_chroma(nnz_c, k, ci, br, bc)
                        tc = _write_residual_block(
                            w, cac_lvl[ci, br, bc][1:], nc, 15)
                        nnz_c[k, ci, br, bc] = tc

        # expected recon (decode path)
        pred_y = 128 if edge_y is None else (int(edge_y.sum()) + 8) >> 4
        f = _H4 @ dc_lvl @ _H4
        dcY = _dequant_luma_dc(f, qp)
        rec = np.zeros((16, 16), np.int64)
        for br in range(4):
            for bc in range(4):
                d = np.zeros(16, np.int64)
                d[ZIGZAG4_NP] = ac_lvl[br, bc]
                d = _dequant4_ac(d.reshape(4, 4), qp)
                d[0, 0] = dcY[br, bc]
                rec[br * 4:br * 4 + 4, bc * 4:bc * 4 + 4] = np.clip(
                    pred_y + ((_inv4(d) + 32) >> 6), 0, 255)
        exp_y[:, k * 16:k * 16 + 16] = rec
        edge_y = rec[:, 15]
        crec = np.zeros((2, 8, 8), np.int64)
        for ci in range(2):
            if edge_c is None:
                cp = np.full((8, 8), 128, np.int64)
            else:
                e = edge_c[ci]
                cp = np.zeros((8, 8), np.int64)
                cp[0:4] = (int(e[0:4].sum()) + 2) >> 2
                cp[4:8] = (int(e[4:8].sum()) + 2) >> 2
            f2 = H2 @ cdc_lvl[ci] @ H2
            cdcq = _dequant_chroma_dc(f2, qpc)
            for br in range(2):
                for bc in range(2):
                    d = np.zeros(16, np.int64)
                    d[ZIGZAG4_NP] = cac_lvl[ci, br, bc]
                    d = _dequant4_ac(d.reshape(4, 4), qpc)
                    d[0, 0] = cdcq[br, bc]
                    crec[ci, br * 4:br * 4 + 4, bc * 4:bc * 4 + 4] = np.clip(
                        cp[br * 4:br * 4 + 4, bc * 4:bc * 4 + 4]
                        + ((_inv4(d) + 32) >> 6), 0, 255)
        exp_u[:, k * 8:k * 8 + 8] = crec[0]
        exp_v[:, k * 8:k * 8 + 8] = crec[1]
        edge_c = crec[:, :, 7].copy()
    w.rbsp_trailing()
    bs += nal(5, w.to_bytes())
    return bytes(bs), exp_y, exp_u, exp_v


def check(qp, dc, ac, cdc, cac, n_mbs=1, tag=""):
    bs, ey, eu, ev = build_stream(qp, dc, ac, cdc, cac, n_mbs)
    try:
        ry, ru, rv = avshim.decode_h264(bs)
    except Exception as e:
        return f"{tag}: DECODE-FAIL {e}"
    if not (np.array_equal(ry.astype(np.int64), ey)
            and np.array_equal(ru.astype(np.int64), eu)
            and np.array_equal(rv.astype(np.int64), ev)):
        yb = int((ry != ey).sum())
        ub = int((ru != eu).sum())
        vb = int((rv != ev).sum())
        return f"{tag}: MISMATCH y={yb} u={ub} v={vb}"
    return None


def sparse_levels(rng, n_slots, tc, max_mag, t1=None):
    """Random level vector (scan order) with exactly tc nonzeros."""
    v = np.zeros(n_slots, np.int64)
    pos = np.sort(rng.choice(n_slots, size=tc, replace=False))
    mags = rng.integers(1, max_mag + 1, size=tc)
    signs = rng.choice([-1, 1], size=tc)
    v[pos] = mags * signs
    if t1 is not None:
        # force exactly t1 trailing ones at the scan tail
        nz = np.nonzero(v)[0]
        for i, idx in enumerate(nz[::-1]):
            if i < t1:
                v[idx] = rng.choice([-1, 1])
            elif abs(v[idx]) == 1:
                v[idx] = rng.choice([2, -2, 3])
    return v


def main():
    quick = "--quick" in sys.argv
    rng = np.random.default_rng(0)
    fails = []
    zero16 = np.zeros((4, 4), np.int64)
    zac = np.zeros((4, 4, 16), np.int64)
    zcdc = np.zeros((2, 2, 2), np.int64)
    zcac = np.zeros((2, 2, 2, 16), np.int64)

    # ---- 1. chroma DC exhaustive (levels in -2..2, 625^2 too many ->
    # same pattern both components, all 625)
    print("audit: chroma DC ...", flush=True)
    vals = (-2, -1, 0, 1, 2)
    combos = list(itertools.product(vals, repeat=4))
    if quick:
        combos = combos[::13]
    for c in combos:
        cdc = np.array([[ [c[0], c[1]], [c[2], c[3]] ]] * 2, np.int64)
        r = check(30, zero16, zac, cdc, zcac, tag=f"cdc{c}")
        if r:
            fails.append(r)
    print(f"  {len(fails)} failures so far", flush=True)

    # ---- 2. luma DC: random patterns per (tc, t1)
    print("audit: luma DC ...", flush=True)
    for tc in range(0, 17):
        for rep in range(2 if quick else 6):
            scan = sparse_levels(rng, 16, tc, 4)
            dc = np.zeros(16, np.int64)
            dc[ZIGZAG4_NP] = scan
            r = check(30, dc.reshape(4, 4), zac, zcdc, zcac,
                      tag=f"ldc tc={tc} rep={rep}")
            if r:
                fails.append(r)
    print(f"  {len(fails)} failures so far", flush=True)

    # ---- 3. luma AC with nC growth across 4 MBs (exercises ctx 0..3)
    print("audit: luma AC + nC contexts ...", flush=True)
    for tc in range(1, 16):
        for rep in range(2 if quick else 5):
            ac = np.zeros((4, 4, 16), np.int64)
            for br in range(4):
                for bc in range(4):
                    ac[br, bc, 1:] = sparse_levels(rng, 15, tc, 3)
            r = check(30, zero16, ac, zcdc, zcac, n_mbs=4,
                      tag=f"lac tc={tc} rep={rep}")
            if r:
                fails.append(r)
    print(f"  {len(fails)} failures so far", flush=True)

    # ---- 4. chroma AC with context growth
    print("audit: chroma AC ...", flush=True)
    for tc in range(1, 16):
        for rep in range(1 if quick else 3):
            cac = np.zeros((2, 2, 2, 16), np.int64)
            for ci in range(2):
                for br in range(2):
                    for bc in range(2):
                        cac[ci, br, bc, 1:] = sparse_levels(rng, 15, tc, 3)
            r = check(30, zero16, zac, zcdc, cac, n_mbs=4,
                      tag=f"cac tc={tc} rep={rep}")
            if r:
                fails.append(r)
    print(f"  {len(fails)} failures so far", flush=True)

    # ---- 5. big levels (escape paths) at low qp. Magnitudes are capped so
    # dequantized coefficients stay inside the spec's +-2^15 conformance
    # bound (qp=10 -> |level| <= ~500); beyond that libavcodec clamps at
    # int16 and the comparison is meaningless.
    print("audit: level escapes ...", flush=True)
    for mag in (14, 15, 16, 30, 31, 100, 300, 500):
        for tc in (1, 3, 6):
            scan = sparse_levels(rng, 15, tc, 2)
            nz = np.nonzero(scan)[0]
            scan[nz[0]] = mag
            ac = np.zeros((4, 4, 16), np.int64)
            ac[0, 0, 1:] = scan
            r = check(10, zero16, ac, zcdc, zcac,
                      tag=f"esc mag={mag} tc={tc}")
            if r:
                fails.append(r)
    # ---- 6. total_zeros sweep: tc nonzeros packed at controlled offset
    print("audit: total_zeros ...", flush=True)
    for tc in range(1, 16):
        for tz in range(0, 16 - tc):
            scan = np.zeros(15, np.int64)
            # put tc coeffs with total zeros below the last one == tz
            pos = list(range(tz, tz + tc))
            for p in pos:
                scan[p] = rng.choice([-2, 2, 1, -1])
            if tc + tz <= 15:
                r = check(30, zero16,
                          _mk_ac(scan), zcdc, zcac,
                          tag=f"tz tc={tc} tz={tz}")
                if r:
                    fails.append(r)
    print(f"total failures: {len(fails)}")
    for f in fails[:60]:
        print(" ", f)
    return 0 if not fails else 1


def _mk_ac(scan):
    ac = np.zeros((4, 4, 16), np.int64)
    ac[0, 0, 1:] = scan
    return ac


if __name__ == "__main__":
    sys.exit(main())
