#!/usr/bin/env python
"""Empirically derive the ChromaArrayType-3 (4:4:4 / monochrome)
coded_block_pattern me(v) mapping — Table 9-4's "0 or 3" inter column —
against libavcodec, and check it matches the committed table
(codecs/h264_tables.CBP444_INTER_CBP2CODE).

Method (no spec table assumed): for every cbp value 0..15 we hand-write
a one-MB Hi444PP P slice whose residual blocks cover EXACTLY the 8x8
luma groups in ``cbp``, once per candidate code_num 0..15 written as the
coded_block_pattern ue(v). Only the correct code_num parses: a wrong one
makes ffmpeg derive a different cbp, desyncing the residual parse —
decode fails or reconstructs differently. The candidate whose decode
byte-matches our predicted reconstruction is the code for that cbp; the
scan asserts it is unique. cbp == 0 is exercised with a coded MB that
carries a nonzero motion vector (that is how the production encoder
emits cbp 0: ops/h264_planes444._assemble_p_444 writes the cbp code for
every coded MB, including pure-motion ones).

The reference streams fullcolor by negotiating Hi444PP from x264/NVENC
(reference src/selkies/rtc.py:649-717); our encoder emits the bits
itself, so this mapping must be independently verified.

Run: python tools/derive_cbp444.py   (needs the libavcodec shim)
"""

from __future__ import annotations

import sys

import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from selkies_tpu.codecs import h264 as H            # noqa: E402
from selkies_tpu.codecs import h264_tables as T     # noqa: E402
from selkies_tpu.native import avshim               # noqa: E402

QP = 28
_GROUPS = {g: [(br, bc) for br in range(4) for bc in range(4)
               if (br // 2) * 2 + (bc // 2) == g] for g in range(4)}


def _i444_au() -> tuple[bytes, list[np.ndarray]]:
    """Headers + a textured I444 AU; returns the encoder's decoder-exact
    recon planes (texture makes motion compensation observable)."""
    rng = np.random.default_rng(444)
    enc = H.I444Encoder(16, 16, QP)
    planes = [rng.integers(40, 216, (16, 16)).astype(np.uint8)
              for _ in range(3)]
    au = enc.encode_frame(*planes)
    return enc.headers() + au, [p.astype(np.int64) for p in enc.recon]


def _p444_mb_au(cbp: int, code_num: int, res_y: np.ndarray,
                mvd: tuple[int, int]) -> bytes:
    """One-MB P slice: residual blocks written for exactly the groups in
    ``cbp`` (luma component; chroma components carry zero coefficients in
    the same coded groups), coded_block_pattern written as
    ue(code_num)."""
    lvl = np.zeros((4, 4, 16), np.int64)
    for br in range(4):
        for bc in range(4):
            wm = H._fwd4(res_y[br * 4:br * 4 + 4, bc * 4:bc * 4 + 4])
            lvl[br, bc] = H._quant4_inter(wm, QP).reshape(16)[T.ZIGZAG4_NP]

    w = H.BitWriter()
    H.p_slice_header_bits(w, 0, QP, 1)
    w.ue(0)                           # mb_skip_run
    w.ue(0)                           # mb_type P_L0_16x16
    w.se(mvd[0]); w.se(mvd[1])        # mvd (quarter-pel units)
    w.ue(code_num)                    # coded_block_pattern me(v) candidate
    if cbp != 0:
        w.se(0)                       # mb_qp_delta (present iff cbp != 0)
        nnz = np.zeros((3, 1, 4, 4), np.int64)
        for ci in range(3):
            for br, bc in H.LUMA_BLK_ORDER:
                g8 = (br // 2) * 2 + (bc // 2)
                if not (cbp >> g8) & 1:
                    continue
                nc = H.I16Encoder._nc_luma(nnz[ci], 0, br, bc)
                coeffs = lvl[br, bc] if ci == 0 else np.zeros(16, np.int64)
                nnz[ci, 0, br, bc] = H._write_residual_block(
                    w, coeffs, nc, 16)
    w.rbsp_trailing()
    return H.nal(1, w.to_bytes(), ref_idc=2)


def _mc_shift(ref: np.ndarray, dx: int, dy: int) -> np.ndarray:
    """Full-pel MC with picture-edge extension on a one-MB picture."""
    ys = np.clip(np.arange(16) + dy, 0, 15)
    xs = np.clip(np.arange(16) + dx, 0, 15)
    return ref[np.ix_(ys, xs)]


def _predicted_recon(cbp: int, res_y: np.ndarray,
                     refs: list[np.ndarray], dx: int, dy: int
                     ) -> list[np.ndarray]:
    """Decoder-exact recon for the crafted MB, all three components."""
    preds = [_mc_shift(r, dx, dy) for r in refs]
    out = []
    for ci, pred in enumerate(preds):
        rec = np.empty((16, 16), np.int64)
        for br in range(4):
            for bc in range(4):
                g8 = (br // 2) * 2 + (bc // 2)
                d = np.zeros(16, np.int64)
                if ci == 0 and (cbp >> g8) & 1:
                    wm = H._fwd4(res_y[br * 4:br * 4 + 4,
                                       bc * 4:bc * 4 + 4])
                    d[T.ZIGZAG4_NP] = \
                        H._quant4_inter(wm, QP).reshape(16)[T.ZIGZAG4_NP]
                d = H._dequant4_ac(d.reshape(4, 4), QP)
                r = (H._inv4(d) + 32) >> 6
                rec[br * 4:br * 4 + 4, bc * 4:bc * 4 + 4] = np.clip(
                    pred[br * 4:br * 4 + 4, bc * 4:bc * 4 + 4] + r, 0, 255)
        out.append(rec.astype(np.uint8))
    return out


def derive() -> np.ndarray:
    """cbp -> code_num by exhaustive candidate scan against ffmpeg."""
    head_au, refs = _i444_au()
    mapping = np.full(16, -1, np.int64)
    for cbp in range(16):
        # cbp 0 rides a pure-motion MB (mv = 1 full pel right) so the
        # reconstruction is distinguishable from both skip and every
        # wrong-cbp parse; others use zero MV + group-exact residual
        mvd = (4, 0) if cbp == 0 else (0, 0)
        dx, dy = mvd[0] // 4, mvd[1] // 4
        res = np.zeros((16, 16), np.int64)
        for g in range(4):
            if (cbp >> g) & 1:
                for br, bc in _GROUPS[g]:
                    res[br * 4:br * 4 + 4, bc * 4:bc * 4 + 4] = 60
        want = _predicted_recon(cbp, res, refs, dx, dy)
        hits = []
        for code in range(16):
            au = _p444_mb_au(cbp, code, res, mvd)
            try:
                sess = avshim.H264Session()
                got = None
                for chunk in (head_au, au):
                    got = sess.decode(chunk) or got
                got = sess.flush() or got
                sess.close()
            except (ValueError, RuntimeError):
                continue
            if got is not None and got[0].shape == (16, 16) \
                    and all(np.array_equal(got[ci], want[ci])
                            for ci in range(3)):
                hits.append(code)
        assert len(hits) == 1, \
            f"cbp {cbp}: candidates {hits} all decode-match (want exactly 1)"
        mapping[cbp] = hits[0]
    return mapping.astype(np.int32)


def main() -> int:
    if not avshim.available():
        print("libavcodec shim unavailable; cannot derive", file=sys.stderr)
        return 2
    derived = derive()
    print("derived cbp -> code_num:", derived.tolist())
    print("committed table:        ",
          T.CBP444_INTER_CBP2CODE.tolist())
    if np.array_equal(derived, T.CBP444_INTER_CBP2CODE):
        print("MATCH: CBP444_INTER_CBP2CODE is conformant")
        return 0
    print("MISMATCH — the committed table is wrong", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
