"""Microbenchmarks steering the TPU layout redesign of the H.264 path.

Answers, on the live backend:
1. small-table lookup: jnp.take vs one-hot f32 matmul (CAVLC tables)
2. scatter-add cost at bitstream-packer scale
3. plane-sliced butterfly transform vs the (..., 4, 4) einsum layout
4. motion SAD reduce cost at candidate-set scale
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from selkies_tpu.compile_cache import enable as enable_compile_cache

enable_compile_cache(jax)


def t(fn, *args, n=5, warm=2):
    for _ in range(warm):
        jax.block_until_ready(fn(*args))
    t0 = time.monotonic()
    for _ in range(n):
        jax.block_until_ready(fn(*args))
    return (time.monotonic() - t0) / n


def main():
    print("backend:", jax.default_backend(), flush=True)
    rng = np.random.default_rng(0)

    # --- 1. table lookup: (272, 480) indices into a 272-entry table ------
    table = jnp.asarray(rng.integers(0, 1 << 20, 272, dtype=np.int32))
    idx = jnp.asarray(rng.integers(0, 272, (272, 480), dtype=np.int32))

    f_take = jax.jit(lambda ix: jnp.take(table, ix))
    print(f"take 272-table (272,480) idx: {t(f_take, idx)*1e3:.3f} ms",
          flush=True)

    tab_f = table.astype(jnp.float32)

    def onehot_lookup(ix):
        oh = (ix[..., None] == jnp.arange(272, dtype=jnp.int32)) \
            .astype(jnp.float32)
        return (oh @ tab_f).astype(jnp.int32)
    f_oh = jax.jit(onehot_lookup)
    print(f"one-hot f32 matmul lookup:    {t(f_oh, idx)*1e3:.3f} ms",
          flush=True)

    # 30 lookups fused in one program (the per-frame reality)
    f_take30 = jax.jit(lambda ix: sum(
        jnp.take(table, (ix + k) % 272) for k in range(30)))
    print(f"take x30 fused:               {t(f_take30, idx)*1e3:.3f} ms",
          flush=True)

    # --- 2. scatter-add at packer scale ---------------------------------
    R, S, w_cap = 68, 105491, 23040
    vals = jnp.asarray(rng.integers(0, 1 << 31, (R, S), dtype=np.int64)
                       .astype(np.uint32))
    # monotone per-row offsets like real bit offsets (~73 bits/MB avg)
    offs = np.sort(rng.integers(0, w_cap, (R, S), dtype=np.int32), axis=1)
    base = (np.arange(R, dtype=np.int32) * w_cap)[:, None]
    flat_idx = jnp.asarray((offs + base).reshape(-1))
    fvals = vals.reshape(-1)

    def scat(ix, v):
        return jnp.zeros((R * w_cap,), jnp.uint32).at[ix].add(
            v, mode="drop")
    f_scat = jax.jit(scat)
    print(f"scatter-add {R*S/1e6:.1f}M -> {R*w_cap/1e6:.1f}M words: "
          f"{t(f_scat, flat_idx, fvals)*1e3:.3f} ms", flush=True)

    # same but 2 scatters (the real packer does hi+lo)
    f_scat2 = jax.jit(lambda ix, v: scat(ix, v) + scat(ix, v ^ 1))
    print(f"scatter-add x2:               "
          f"{t(f_scat2, flat_idx, fvals)*1e3:.3f} ms", flush=True)

    # --- 3. transforms: plane butterflies vs (...,4,4) einsum ------------
    H, W = 1088, 1920
    x = jnp.asarray(rng.integers(0, 256, (H, W), dtype=np.int32))

    def fwd_planes(p):
        x0, x1, x2, x3 = (p[0::4, :], p[1::4, :], p[2::4, :], p[3::4, :])
        s0, s1, d0, d1 = x0 + x3, x1 + x2, x0 - x3, x1 - x2
        rows = (s0 + s1, 2 * d0 + d1, s0 - s1, d0 - 2 * d1)
        out = []
        for r in rows:
            c0, c1, c2, c3 = (r[:, 0::4], r[:, 1::4], r[:, 2::4],
                              r[:, 3::4])
            s0, s1, d0, d1 = c0 + c3, c1 + c2, c0 - c3, c1 - c2
            out.extend([s0 + s1, 2 * d0 + d1, s0 - s1, d0 - 2 * d1])
        return sum(out)          # reduce so nothing is DCE'd
    f_pl = jax.jit(fwd_planes)
    print(f"fwd4 plane-sliced ({H}x{W}):  {t(f_pl, x)*1e3:.3f} ms",
          flush=True)

    from selkies_tpu.ops.h264_transform import forward4x4

    def fwd_einsum(p):
        b = p.reshape(H // 4, 4, W // 4, 4).swapaxes(1, 2)
        return forward4x4(b).sum()
    f_es = jax.jit(fwd_einsum)
    print(f"fwd4 einsum (...,4,4):        {t(f_es, x)*1e3:.3f} ms",
          flush=True)

    # --- 4. motion SAD at candidate scale --------------------------------
    cur = jnp.asarray(rng.integers(0, 256, (H, W), dtype=np.int32))
    ref = jnp.asarray(rng.integers(0, 256, (H, W), dtype=np.int32))
    K = 57

    def sad_all(c, r):
        costs = []
        for k in range(K):
            sh = jnp.roll(r, k % 8 - 4, axis=0)
            sad = jnp.abs(c - sh).reshape(68, 16, 120, 16).sum(axis=(1, 3))
            costs.append(sad)
        return jnp.argmin(jnp.stack(costs), axis=0)
    f_sad = jax.jit(sad_all)
    print(f"SAD x{K} cands + argmin:      {t(f_sad, cur, ref)*1e3:.3f} ms",
          flush=True)

    # plane-friendly SAD: reduce via (68,16,120,16) -> strided adds
    def sad_planes(c, r):
        costs = []
        for k in range(K):
            sh = jnp.roll(r, k % 8 - 4, axis=0)
            d = jnp.abs(c - sh)
            # sum 16x16 tiles with large-minor-dim partial sums
            col = d.reshape(68, 16, W).sum(axis=1)          # (68, W)
            costs.append(col.reshape(68, 120, 16).sum(axis=-1))
        return jnp.argmin(jnp.stack(costs), axis=0)
    f_sadp = jax.jit(sad_planes)
    print(f"SAD x{K} plane-reduce:        {t(f_sadp, cur, ref)*1e3:.3f} ms",
          flush=True)


if __name__ == "__main__":
    main()
