#!/usr/bin/env python
"""Perf ledger: the durable efficiency trajectory `bench.py` runs feed.

Every bench run used to be a throwaway JSON line — the driver recorded
one number per round and the trajectory between rounds (did the
plane-layout codec help? did the readback lever regress p99?) lived
nowhere. This tool keeps an **append-only JSONL ledger** of bench runs,
keyed by (git rev, host fingerprint, backend class, resolution, codec,
backend_health), and turns it into a regression gate:

  record   append a bench JSON document (file or stdin) to the ledger
  check    compare a candidate run against the last ACCEPTED baseline
           for the same key within a noise band; exits non-zero on a
           regression beyond the band (unless --warn-only)
  report   render the fps / p99 / top-stage trajectory per key
  pareto   render the quality x latency x energy operating-point front
           (ISSUE 14) over the energy-bearing entries

Energy columns (``joules_frame`` / ``fps_per_w`` / ``watts_mean`` /
``energy_source``, ISSUE 14) are carried on every entry but are
**informational-only in check** — never gated — until a real-TPU
baseline entry exists: the CPU proxy coefficients rank operating points
against each other, they are not absolute joules, and a coefficient
retune must never fail the CPU perf-gate.

Baseline rules (the r4/r5 lesson — a silent CPU fallback must never
become the number to beat):

- only runs whose ``backend_health.status == "ok"`` are
  baseline-eligible; a ``cpu-fallback-*`` run records with
  ``baseline_eligible: false`` and can never be compared against, and a
  non-ok-health candidate is never *compared* — it exits 3 ("no
  gateable number", 0 under --warn-only) so a regression that also
  breaks health cannot slip through a hard-fail gate;
- the comparison key includes the backend CLASS (``cpu`` vs ``tpu`` …),
  so a CPU run is never judged against a TPU baseline even when both
  are healthy;
- the key includes the host fingerprint (same digest the compile cache
  uses) so a laptop run never gates a CI runner; ``--ignore-host``
  relaxes that for fleet-style gates that accept cross-host noise.

Stdlib-only (the CI lint image runs it); the host fingerprint comes
from selkies_tpu.compile_cache, which is itself stdlib-only.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import subprocess
import sys
from typing import Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from selkies_tpu.compile_cache import host_fingerprint, host_id  # noqa: E402

#: default append-only ledger, committed so the trajectory survives
#: across rounds/sessions (PERF.md points here)
DEFAULT_LEDGER = os.path.join(_REPO, "PERF_LEDGER.jsonl")

#: relative noise band for check: a metric may move this much against
#: the baseline before it counts as a regression (CPU CI runners are
#: noisy; the TPU bench is steadier but shares the band for now)
DEFAULT_BAND = 0.15


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _git_rev() -> str:
    try:
        r = subprocess.run(["git", "rev-parse", "HEAD"], cwd=_REPO,
                           capture_output=True, text=True, timeout=10)
        if r.returncode == 0:
            return r.stdout.strip()
    except Exception:
        pass
    return "unknown"


def backend_class(backend: str) -> str:
    """'cpu-fallback-relay-dead' -> 'cpu'; 'tpu'/'axon'/'cuda' pass
    through. The class — not the full label — keys baseline matching."""
    b = (backend or "unknown").lower()
    if b.startswith("cpu"):
        return "cpu"
    return b.split("-", 1)[0]


def entry_from_bench(doc: dict, *, git_rev: Optional[str] = None,
                     host: Optional[str] = None,
                     accept: Optional[bool] = None) -> dict:
    """Curate one bench JSON document into a ledger entry. Keeps the
    trajectory fields (fps, latency percentiles, per-stage ms, perf /
    occupancy summaries) and the key fields; drops the rest."""
    metric = str(doc.get("metric", ""))
    res = "unknown"
    codec = "unknown"
    # encode_fps_1920x1080_h264_tpu -> resolution + codec
    parts = metric.split("_")
    for p in parts:
        if "x" in p and p.replace("x", "").isdigit():
            res = p
    if len(parts) >= 2 and parts[0] == "encode" and len(parts) >= 4:
        codec = parts[3]
    elif parts and parts[-1] in ("h264", "jpeg"):
        codec = parts[-1]          # stripe_scaling_WxH_h264 style metrics
    health = doc.get("backend_health") or {}
    status = health.get("status", "unknown")
    eligible = status == "ok" if accept is None else bool(accept)
    return {
        "v": 1,
        "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"),
        "git_rev": git_rev or _git_rev(),
        "host": host or host_fingerprint(),
        # stable per-machine id (fingerprint is shared across identical
        # fleet hosts by design); joins ledger rows with flight-recorder
        # incidents and structured logs after the fact
        "host_id": host_id(),
        "metric": metric,
        "backend": doc.get("backend", "unknown"),
        "backend_class": backend_class(doc.get("backend", "unknown")),
        "resolution": res,
        "codec": codec,
        "backend_health": status,
        "baseline_eligible": eligible,
        "fps": doc.get("value"),
        "vs_baseline": doc.get("vs_baseline"),
        "latency_p50_ms": doc.get("latency_p50_ms"),
        "latency_p99_ms": doc.get("latency_p99_ms"),
        "stages_ms": doc.get("stages_ms"),
        "stage_sum_ms": doc.get("stage_sum_ms"),
        "qoe_score": (doc.get("qoe") or {}).get("score"),
        "g2g_p50_ms": (doc.get("glass_to_glass") or {}).get("p50_ms"),
        "g2g_p99_ms": (doc.get("glass_to_glass") or {}).get("p99_ms"),
        # deep pipeline (ROADMAP 2): the depth the run was configured
        # for and the cross-frame overlap it actually achieved — the
        # serial-vs-pipelined acceptance pair lives in these two columns
        "pipeline_depth": doc.get("pipeline_depth"),
        # split-frame device parallelism (ROADMAP 2): the CHOSEN shard
        # count (post-degradation — parallel/stripes.stripe_mesh), so a
        # silently degraded mesh can never masquerade as a scaling
        # result, plus the bench's sharded-scaling summary when the
        # --stripes phase ran
        "stripe_devices": doc.get("stripe_devices", 1),
        "stripes": doc.get("stripes"),
        "overlap_fraction": (doc.get("occupancy") or {})
        .get("overlap_fraction"),
        "occupancy": doc.get("occupancy"),
        "perf_steps": {
            s["name"]: {"roofline_ms": s["roofline_ms"],
                        "bytes_accessed": s["bytes_accessed"],
                        "flops": s["flops"]}
            for s in (doc.get("perf") or {}).get("steps", [])
            if not s.get("error")
        } or None,
        "hbm_peak_mb": doc.get("hbm_peak_mb"),
        "compile_total_s": doc.get("compile_total_s"),
        # energy axis (ISSUE 14): joules/frame + fps/W with the honest
        # provenance label (proxy|rapl|device) — informational in
        # check until a real-TPU baseline pins the absolute scale
        "joules_frame": (doc.get("energy") or {}).get("joules_frame"),
        "fps_per_w": (doc.get("energy") or {}).get("fps_per_w"),
        "watts_mean": (doc.get("energy") or {}).get("watts_mean"),
        "energy_source": (doc.get("energy") or {}).get("source"),
        # damage-proportional encoding (ROADMAP 4): the run's steady-
        # state dirty fraction and classified content — without them
        # two rows at different damage loads would read as a perf swing
        "dirty_fraction": doc.get("dirty_fraction"),
        "content_class": doc.get("content_class"),
        # the --adaptive acceptance block (encode ms vs dirty fraction,
        # content-class timeline) when that phase ran
        "adaptive": doc.get("adaptive"),
        # broadcast plane (ISSUE 17): fan-out scale of a --broadcast
        # row — device work must track renditions, never viewers, so
        # both axes belong in the trajectory
        "viewers": doc.get("viewers"),
        "renditions": doc.get("renditions"),
        # live fleet soak (ISSUE 19): the scale of a --fleet-live row —
        # how many REAL engine-host processes the contract ran over and
        # how many seats actually moved (drain + failover); a contract
        # pass at 2 hosts and at 10 are different claims
        "fleet_hosts": doc.get("fleet_hosts"),
        "migrations": doc.get("migrations"),
    }


def read_ledger(path: str) -> list[dict]:
    entries: list[dict] = []
    if not os.path.exists(path):
        return entries
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except json.JSONDecodeError:
                log(f"warning: {path}:{i + 1}: unparseable line skipped")
                continue
            if isinstance(e, dict):
                entries.append(e)
    return entries


def append_entry(path: str, entry: dict) -> None:
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(entry, sort_keys=True) + "\n")


def entry_key(e: dict, ignore_host: bool = False) -> tuple:
    key = (e.get("backend_class", "unknown"), e.get("resolution"),
           e.get("codec"))
    if not ignore_host:
        key = (e.get("host"),) + key
    return key


def _same_run(a: dict, b: dict) -> bool:
    """Heuristic identity for 'this ledger entry IS the candidate run':
    bench auto-appends every run, so `check --candidate out.json` would
    otherwise match the candidate against its own ledger copy (same
    rev, same numbers) and always pass."""
    return (a.get("git_rev") == b.get("git_rev")
            and a.get("fps") == b.get("fps")
            and a.get("latency_p99_ms") == b.get("latency_p99_ms"))


def find_baseline(entries: list[dict], candidate: dict,
                  ignore_host: bool = False) -> Optional[dict]:
    """Most recent baseline-eligible entry with the candidate's key.
    The class key is what guarantees a cpu-fallback candidate (class
    ``cpu``) can never be measured against a TPU baseline."""
    want = entry_key(candidate, ignore_host)
    for e in reversed(entries):
        if e is candidate or _same_run(e, candidate):
            continue
        if not e.get("baseline_eligible"):
            continue
        if not str(e.get("metric", "")).startswith("encode_fps"):
            continue
        if entry_key(e, ignore_host) == want:
            return e
    return None


def compare(candidate: dict, baseline: dict,
            band: float = DEFAULT_BAND) -> list[str]:
    """-> list of regression descriptions beyond the noise band (empty
    = within band). fps gates downward moves, p99 upward ones."""
    # epsilon keeps the band edge out of float-rounding territory: a
    # move of EXACTLY band is tolerated, band+delta is not
    eps = 1e-9
    problems: list[str] = []
    fps_new, fps_old = candidate.get("fps"), baseline.get("fps")
    if isinstance(fps_new, (int, float)) and isinstance(
            fps_old, (int, float)) and fps_old > 0:
        if 1.0 - fps_new / fps_old > band + eps:
            problems.append(
                f"fps {fps_new} vs baseline {fps_old} "
                f"({fps_new / fps_old - 1.0:+.1%}, band ±{band:.0%})")
    p99_new = candidate.get("latency_p99_ms")
    p99_old = baseline.get("latency_p99_ms")
    if isinstance(p99_new, (int, float)) and isinstance(
            p99_old, (int, float)) and p99_old > 0:
        if p99_new / p99_old - 1.0 > band + eps:
            problems.append(
                f"latency_p99 {p99_new}ms vs baseline {p99_old}ms "
                f"({p99_new / p99_old - 1.0:+.1%}, band ±{band:.0%})")
    return problems


def _load_candidate(args: argparse.Namespace,
                    entries: list[dict]) -> Optional[dict]:
    """The run under test: an explicit bench JSON (``--candidate``,
    '-' = stdin) or the newest encode_fps entry already in the ledger."""
    if args.candidate:
        raw = sys.stdin.read() if args.candidate == "-" else \
            open(args.candidate, encoding="utf-8").read()
        doc = json.loads(raw)
        if "baseline_eligible" in doc:     # already a ledger entry
            return doc
        return entry_from_bench(doc)
    for e in reversed(entries):
        if str(e.get("metric", "")).startswith("encode_fps"):
            return e
    return None


def cmd_record(args: argparse.Namespace) -> int:
    raw = sys.stdin.read() if args.file == "-" else \
        open(args.file, encoding="utf-8").read()
    doc = json.loads(raw)
    accept = True if args.accept else (False if args.reject else None)
    entry = entry_from_bench(doc, accept=accept)
    append_entry(args.ledger, entry)
    log(f"recorded {entry['metric']} fps={entry['fps']} "
        f"backend={entry['backend']} eligible={entry['baseline_eligible']} "
        f"-> {args.ledger}")
    print(json.dumps(entry, sort_keys=True))
    return 0


def cmd_check(args: argparse.Namespace) -> int:
    entries = read_ledger(args.ledger)
    candidate = _load_candidate(args, entries)
    if candidate is None:
        log("check: no candidate run (empty ledger, no --candidate)")
        return 0 if args.warn_only else 2
    status = candidate.get("backend_health")
    if status not in ("ok", "degraded", "failed"):
        # schema drift or the wrong file: a gate that silently stops
        # gating is the r4/r5 failure all over again — fail loudly
        log(f"check: candidate has no recognisable backend_health "
            f"({status!r}) — malformed candidate?")
        return 0 if args.warn_only else 2
    if status != "ok":
        # never *compare* an unhealthy number — but never let it slide
        # through a hard-fail gate either: a regression that also tips
        # health to degraded/failed must not read as green. Distinct rc
        # so CI can tell "no gateable number" from "within band".
        log(f"check: candidate backend_health={status!r} "
            f"(backend {candidate.get('backend')!r}) — not a gating "
            f"number, skipping comparison")
        return 0 if args.warn_only else 3
    baseline = find_baseline(entries, candidate,
                             ignore_host=args.ignore_host)
    if baseline is None:
        log(f"check: no accepted baseline for key "
            f"{entry_key(candidate, args.ignore_host)} — nothing to "
            f"compare (this run becomes the baseline once recorded)")
        return 0
    problems = compare(candidate, baseline, band=args.band)
    # energy columns are INFORMATIONAL-ONLY (ISSUE 14): logged, never
    # appended to problems — a wild joules swing (coefficient retune,
    # RAPL appearing on one runner) must not fail the CPU perf-gate
    # until a real-TPU baseline entry pins the absolute scale
    jf_new = candidate.get("joules_frame")
    jf_old = baseline.get("joules_frame")
    if isinstance(jf_new, (int, float)) and \
            isinstance(jf_old, (int, float)) and jf_old > 0:
        log(f"check: energy joules_frame {jf_new} vs baseline {jf_old} "
            f"({jf_new / jf_old - 1.0:+.1%}, "
            f"source {candidate.get('energy_source')!r} vs "
            f"{baseline.get('energy_source')!r}) — informational only, "
            f"never gated")
    log(f"check: candidate {candidate.get('git_rev', '?')[:7]} "
        f"fps={candidate.get('fps')} p99={candidate.get('latency_p99_ms')} "
        f"vs baseline {baseline.get('git_rev', '?')[:7]} "
        f"fps={baseline.get('fps')} p99={baseline.get('latency_p99_ms')}")
    if not problems:
        log("check: within noise band — OK")
        return 0
    for p in problems:
        log(f"REGRESSION: {p}")
    if args.warn_only:
        log("check: --warn-only set; not failing")
        return 0
    return 1


def _top_stage(e: dict) -> str:
    stages = e.get("stages_ms") or {}
    if not stages:
        return "-"
    name, ms = max(stages.items(), key=lambda kv: kv[1] or 0.0)
    return f"{name}={ms}ms"


def cmd_report(args: argparse.Namespace) -> int:
    entries = [e for e in read_ledger(args.ledger)
               if str(e.get("metric", "")).startswith("encode_fps")]
    if not entries:
        log("report: ledger is empty")
        return 0
    by_key: dict[tuple, list[dict]] = {}
    for e in entries:
        by_key.setdefault(entry_key(e, args.ignore_host), []).append(e)
    out_doc: dict = {"keys": []}
    for key, runs in sorted(by_key.items(), key=lambda kv: str(kv[0])):
        print(f"== {' / '.join(str(k) for k in key)} ({len(runs)} runs)")
        print(f"   {'date':<20} {'rev':<8} {'backend':<24} {'fps':>7} "
              f"{'p50_ms':>9} {'p99_ms':>9} {'g2g_p99':>9} {'pd':>3} "
              f"{'sd':>3} {'overlap':>8} {'j/f':>8} {'fps/W':>7} "
              f"{'df':>5} {'class':>7} {'ok':>3}  top stage")
        for e in runs:
            ov = e.get("overlap_fraction")
            jf = e.get("joules_frame")
            fpw = e.get("fps_per_w")
            df = e.get("dirty_fraction")
            print(f"   {str(e.get('ts', ''))[:19]:<20} "
                  f"{str(e.get('git_rev', ''))[:7]:<8} "
                  f"{str(e.get('backend', ''))[:24]:<24} "
                  f"{e.get('fps') if e.get('fps') is not None else '-':>7} "
                  f"{e.get('latency_p50_ms') or '-':>9} "
                  f"{e.get('latency_p99_ms') or '-':>9} "
                  f"{e.get('g2g_p99_ms') or '-':>9} "
                  f"{e.get('pipeline_depth') or '-':>3} "
                  f"{e.get('stripe_devices') or 1:>3} "
                  f"{(format(ov, '.1%') if isinstance(ov, (int, float)) else '-'):>8} "
                  f"{(format(jf, '.3f') if isinstance(jf, (int, float)) else '-'):>8} "
                  f"{(format(fpw, '.3f') if isinstance(fpw, (int, float)) else '-'):>7} "
                  f"{(format(df, '.2f') if isinstance(df, (int, float)) else '-'):>5} "
                  f"{str(e.get('content_class') or '-')[:7]:>7} "
                  f"{'y' if e.get('baseline_eligible') else 'n':>3}  "
                  f"{_top_stage(e)}")
        out_doc["keys"].append({
            "key": list(key),
            "runs": [{k: e.get(k) for k in
                      ("ts", "git_rev", "backend", "fps",
                       "latency_p50_ms", "latency_p99_ms", "g2g_p99_ms",
                       "pipeline_depth", "stripe_devices",
                       "overlap_fraction", "joules_frame", "fps_per_w",
                       "energy_source", "dirty_fraction",
                       "content_class",
                       "baseline_eligible", "stages_ms")}
                     for e in runs]})
    if args.json:
        print(json.dumps(out_doc, sort_keys=True))
    return 0


def _pareto_points(entries: list[dict]) -> list[dict]:
    """Latest energy-bearing entry per operating point. An operating
    point is a prewarm-lattice-shaped key — (backend class, resolution,
    codec, stripe devices, pipeline depth): the axes the ladder and the
    lattice actually move between."""
    latest: dict = {}
    for e in entries:
        if not str(e.get("metric", "")).startswith("encode_fps"):
            continue
        if not isinstance(e.get("joules_frame"), (int, float)):
            continue
        lat = e.get("g2g_p99_ms")
        lat = lat if isinstance(lat, (int, float)) else \
            e.get("latency_p99_ms")
        if not isinstance(lat, (int, float)):
            continue
        q = e.get("qoe_score")
        quality = q if isinstance(q, (int, float)) else e.get("fps")
        if not isinstance(quality, (int, float)):
            continue
        # content_class joins the operating-point key (ROADMAP 4): a
        # static-desktop row and a full-motion row are different
        # operating points on the quality x latency x energy surface,
        # not noise around one point
        key = (e.get("backend_class"), e.get("resolution"),
               e.get("codec"), e.get("stripe_devices") or 1,
               e.get("pipeline_depth") or 1,
               e.get("content_class") or "any")
        latest[key] = {            # later entries override: latest wins
            "point": "/".join(str(k) for k in key),
            "quality": quality,
            "quality_axis": "qoe_score"
            if isinstance(q, (int, float)) else "fps",
            "latency_ms": lat,
            "joules_frame": e["joules_frame"],
            "fps_per_w": e.get("fps_per_w"),
            "source": e.get("energy_source"),
            "ts": e.get("ts"), "git_rev": str(e.get("git_rev", ""))[:7],
        }
    return list(latest.values())


def _dominates(a: dict, b: dict) -> bool:
    """a dominates b on the quality x latency x energy surface: no
    worse on every axis, strictly better on at least one."""
    ge = (a["quality"] >= b["quality"]
          and a["latency_ms"] <= b["latency_ms"]
          and a["joules_frame"] <= b["joules_frame"])
    strict = (a["quality"] > b["quality"]
              or a["latency_ms"] < b["latency_ms"]
              or a["joules_frame"] < b["joules_frame"])
    return ge and strict


def cmd_pareto(args: argparse.Namespace) -> int:
    entries = read_ledger(args.ledger)
    points = _pareto_points(entries)
    if not points:
        log("pareto: no energy-bearing encode_fps entries in the "
            "ledger yet (run bench.py)")
        return 0
    for p in points:
        p["front"] = not any(_dominates(q, p) for q in points
                             if q is not p)
    points.sort(key=lambda p: (not p["front"], p["joules_frame"]))
    n_front = sum(p["front"] for p in points)
    print(f"pareto: {len(points)} operating point(s), {n_front} on the "
          f"quality x latency x energy front")
    print(f"{'':2}{'operating point':<36} {'quality':>9} {'p99_ms':>9} "
          f"{'j/frame':>9} {'fps/W':>8} {'src':>6}  rev")
    for p in points:
        print(f"{'* ' if p['front'] else '  '}"
              f"{p['point']:<36} "
              f"{p['quality']:>9.2f} "
              f"{p['latency_ms']:>9.2f} "
              f"{p['joules_frame']:>9.4f} "
              f"{(format(p['fps_per_w'], '.3f') if isinstance(p['fps_per_w'], (int, float)) else '-'):>8} "
              f"{str(p['source'] or '-'):>6}  {p['git_rev']}")
    if n_front < len(points):
        dominated = [p["point"] for p in points if not p["front"]]
        print(f"  dominated: {', '.join(dominated)}")
    if args.json:
        print(json.dumps({"points": points,
                          "front": [p["point"] for p in points
                                    if p["front"]]}, sort_keys=True))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="tools/perf_ledger.py",
        description=__doc__.splitlines()[0])
    p.add_argument("--ledger", default=os.environ.get(
        "PERF_LEDGER_PATH", DEFAULT_LEDGER),
        help=f"JSONL ledger path (default {DEFAULT_LEDGER}, "
             f"env PERF_LEDGER_PATH)")
    sub = p.add_subparsers(dest="cmd", required=True)

    pr = sub.add_parser("record", help="append a bench JSON to the ledger")
    pr.add_argument("file", nargs="?", default="-",
                    help="bench JSON file ('-' or omitted: stdin)")
    pr.add_argument("--accept", action="store_true",
                    help="force baseline eligibility")
    pr.add_argument("--reject", action="store_true",
                    help="force ineligibility")
    pr.set_defaults(fn=cmd_record)

    pc = sub.add_parser("check",
                        help="gate a candidate against the last baseline")
    pc.add_argument("--candidate",
                    help="bench JSON or ledger-entry file ('-': stdin); "
                         "default: newest ledger entry")
    pc.add_argument("--band", type=float, default=DEFAULT_BAND,
                    help=f"relative noise band (default {DEFAULT_BAND})")
    pc.add_argument("--warn-only", action="store_true",
                    help="report regressions but exit 0 (CI ratchet "
                         "stage 1, like graftlint's baseline)")
    pc.add_argument("--ignore-host", action="store_true",
                    help="match baselines across host fingerprints")
    pc.set_defaults(fn=cmd_check)

    pp = sub.add_parser("report", help="render the perf trajectory")
    pp.add_argument("--json", action="store_true",
                    help="machine-readable output after the table")
    pp.add_argument("--ignore-host", action="store_true",
                    help="group across host fingerprints")
    pp.set_defaults(fn=cmd_report)

    pf = sub.add_parser(
        "pareto",
        help="quality x latency x energy operating-point front "
             "(latest energy-bearing entry per operating point)")
    pf.add_argument("--json", action="store_true",
                    help="machine-readable output after the table")
    pf.set_defaults(fn=cmd_pareto)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
