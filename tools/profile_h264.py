"""Decompose the 1080p H.264 frame time on the live backend.

Times the PRODUCTION pipeline (ops/h264_planes — what the engine runs)
at function granularity, calling the real module functions rather than
restating them (so the profile can't drift from the code):

  csc          rgb -> yuv420 (planes module)
  fwd4 x3      stride-4 plane butterflies, all three components
  cavlc y      stacked CAVLC event build, luma-shaped (15-coeff AC)
  cavlc cac    chroma-AC-shaped
  full I       h264_planes.h264_encode_yuv end to end
  full P       h264_planes.h264_encode_p_yuv end to end (motion on)
  (residual = full - parts ~= quant/dequant + offsets + scatter pack)

Then the engine session steps exactly as the capture thread drives them.
Uses the persistent compile cache (first run pays the builds once).

Crash-resilient output (ISSUE 6 — the r3 profile died mid-run and lost
everything after "+ DC lax.scan"): results are written to ``--out``
(default: PROFILE_H264.json in the repo root) INCREMENTALLY after
every stage, so a relay death keeps every completed measurement with
``"complete": false`` recording how far it got. ``--json`` prints the
same document as one machine-readable line on stdout at the end
(progress moves to stderr).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from selkies_tpu.compile_cache import enable as enable_compile_cache

enable_compile_cache(jax)

ARGS = argparse.Namespace(json=False, out=None)


def log(msg: str) -> None:
    print(msg, file=sys.stderr if ARGS.json else sys.stdout, flush=True)


class ProfileWriter:
    """Incremental stage-result sink. ``add()`` after every measurement
    rewrites the whole (small) JSON document atomically, so the file on
    disk is always valid and always carries every completed stage —
    the property the r3 run lacked when the relay died mid-profile."""

    def __init__(self, path, meta=None):
        self.path = path
        self.doc = {"version": 1, "complete": False,
                    "stages": {}, **(meta or {})}

    def add(self, stage: str, ms: float, **extra) -> None:
        self.doc["stages"][stage] = {"ms": round(ms, 3), **extra}
        self._flush()

    def meta(self, **fields) -> None:
        self.doc.update(fields)
        self._flush()

    def finish(self) -> None:
        self.doc["complete"] = True
        self._flush()

    def _flush(self) -> None:
        if not self.path:
            return
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self.doc, f, indent=1, sort_keys=True)
        os.replace(tmp, self.path)


def t(fn, *args, n=3, warm=1):
    for _ in range(warm):
        jax.block_until_ready(fn(*args))
    t0 = time.monotonic()
    for _ in range(n):
        jax.block_until_ready(fn(*args))
    return (time.monotonic() - t0) / n


def main():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--json", action="store_true",
                   help="machine-readable stdout (one JSON line at the "
                        "end; progress goes to stderr)")
    p.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), os.pardir,
        "PROFILE_H264.json"),
        help="incremental result file (written after EVERY stage; "
             "'' disables)")
    p.parse_args(namespace=ARGS)

    from selkies_tpu.codecs import h264 as hc
    from selkies_tpu.engine.h264_encoder import (H264EncoderSession,
                                                 h264_buffer_caps,
                                                 plan_h264_grid)
    from selkies_tpu.engine.types import CaptureSettings
    from selkies_tpu.ops import h264_encode as He
    from selkies_tpu.ops import h264_planes as Hp

    out_path = os.path.abspath(ARGS.out) if ARGS.out else None
    backend = jax.default_backend()
    w = ProfileWriter(out_path, meta={"backend": backend})
    log(f"backend: {backend}")
    if out_path:
        log(f"incremental results -> {out_path}")
    s = CaptureSettings(capture_width=1920, capture_height=1080,
                        stripe_height=64, output_mode="h264", video_crf=28,
                        use_paint_over=False)
    g = plan_h264_grid(s)
    e_cap, w_cap, out_cap = h264_buffer_caps(g)
    R = g.n_stripes * g.rows_per_stripe
    M = g.mb_w
    H, W = g.height, g.width
    w.meta(grid=f"{W}x{H}", R=R, M=M, e_cap=e_cap, w_cap=w_cap)
    log(f"grid {W}x{H} R={R} M={M} e_cap={e_cap} w_cap={w_cap}")

    rng = np.random.default_rng(0)
    frame = jnp.asarray(rng.integers(0, 256, (H, W, 3), dtype=np.uint8))

    # --- stages (cheap compiles first so a killed run still reports)
    f_csc = jax.jit(Hp.rgb_to_yuv420)
    ms = t(f_csc, frame) * 1e3
    w.add("csc", ms)
    log(f"csc:        {ms:8.2f} ms")
    yf, uf, vf = [jnp.asarray(a) for a in f_csc(frame)]

    f_fwd = jax.jit(lambda y, u, v: sum(
        p for comp in (Hp.fwd4_planes(y), Hp.fwd4_planes(u),
                       Hp.fwd4_planes(v))
        for row in comp for p in row))
    ms = t(f_fwd, yf, uf, vf) * 1e3
    w.add("fwd4_x3", ms)
    log(f"fwd4 x3:    {ms:8.2f} ms")

    # realistic sparsity: ~6 nonzero AC coeffs per 4x4 block at desktop QPs
    def mk_levels(shape):
        lv = rng.integers(-8, 9, (15,) + shape).astype(np.int32)
        keep = rng.random((15,) + shape) < 0.4
        return jnp.asarray(np.where(keep, lv, 0))
    scan_y = mk_levels((H // 4, W // 4))
    nc = jnp.zeros((H // 4, W // 4), jnp.int32)
    f_cavlc = jax.jit(lambda sc, n: Hp.cavlc_events_planes(sc, n)[0])
    ms = t(f_cavlc, scan_y, nc) * 1e3
    w.add("cavlc_y", ms)
    log(f"cavlc y:    {ms:8.2f} ms")
    scan_c = mk_levels((H // 8, W // 8))
    nc_c = jnp.zeros((H // 8, W // 8), jnp.int32)
    f_cavlc_c = jax.jit(lambda sc, n: Hp.cavlc_events_planes(sc, n)[0])
    ms = t(f_cavlc_c, scan_c, nc_c) * 1e3
    w.add("cavlc_cac", ms)
    log(f"cavlc cac:  {ms:8.2f} ms")

    # --- full frame programs (the things that matter)
    pay, nb = hc.slice_header_events(M, R)
    f_i = jax.jit(lambda y, u, v: Hp.h264_encode_yuv(
        y, u, v, 28, jnp.asarray(pay), jnp.asarray(nb), e_cap,
        w_cap).words)
    ti = t(f_i, yf, uf, vf)
    w.add("full_i", ti * 1e3)
    log(f"full I:     {ti * 1e3:8.2f} ms")

    ppay, pnb = hc.p_slice_header_events(M, R)
    cands = He.scroll_candidates(24, 8)
    ry = jnp.asarray(rng.integers(0, 256, (H, W), np.uint8))
    ru = jnp.asarray(rng.integers(0, 256, (H // 2, W // 2), np.uint8))
    rv = jnp.asarray(rng.integers(0, 256, (H // 2, W // 2), np.uint8))
    f_p = jax.jit(lambda y, u, v: Hp.h264_encode_p_yuv(
        y, u, v, ry, ru, rv, 28, jnp.asarray(ppay), jnp.asarray(pnb), 1,
        e_cap, w_cap, candidates=cands,
        stripe_rows=g.rows_per_stripe)[0].words)
    tp = t(f_p, yf, uf, vf)
    w.add("full_p", tp * 1e3, motion_k=len(cands))
    log(f"full P:     {tp * 1e3:8.2f} ms  (motion K={len(cands)})")
    f_p0 = jax.jit(lambda y, u, v: Hp.h264_encode_p_yuv(
        y, u, v, ry, ru, rv, 28, jnp.asarray(ppay), jnp.asarray(pnb), 1,
        e_cap, w_cap, candidates=((0, 0),),
        stripe_rows=g.rows_per_stripe)[0].words)
    ms = t(f_p0, yf, uf, vf) * 1e3
    w.add("full_p_k1", ms)
    log(f"full P K=1: {ms:8.2f} ms (motion cost = delta)")

    # --- full session steps as the engine drives them (the obs.perf
    # wrap records the static cost analysis as a side effect; include
    # it so the saved profile carries roofline context)
    sess = H264EncoderSession(s)
    t_full = t(lambda f: sess.encode(f, force=True)["data"], frame, n=2)
    w.add("session_i", t_full * 1e3)
    log(f"session I step (dispatch+block): {t_full * 1e3:.0f} ms")
    t_p = t(lambda f: sess.encode(f)["data"], frame, n=2)
    w.add("session_p", t_p * 1e3)
    log(f"session P step (dispatch+block): {t_p * 1e3:.0f} ms")
    from selkies_tpu.obs import perf as _perf
    w.meta(perf=_perf.registry.report())
    w.finish()
    if ARGS.json:
        print(json.dumps(w.doc, sort_keys=True))


if __name__ == "__main__":
    main()
