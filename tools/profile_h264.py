"""Decompose the 1080p H.264 frame time on the live backend.

Times each stage of the device program separately (jitted in isolation):
colorspace, transform+scan, CAVLC event build, and the bit-packer's three
internal phases (argsort front-pack, searchsorted compaction, word
materialisation). Run on the real TPU to find where the 4.1 s/frame goes.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from selkies_tpu.compile_cache import enable as enable_compile_cache

enable_compile_cache(jax)   # repeat profiling must not re-pay ~5min builds


def t(fn, *args, n=3, warm=1):
    for _ in range(warm):
        jax.block_until_ready(fn(*args))
    t0 = time.monotonic()
    for _ in range(n):
        jax.block_until_ready(fn(*args))
    return (time.monotonic() - t0) / n


def main():
    from selkies_tpu.engine.h264_encoder import (H264EncoderSession,
                                                 h264_buffer_caps,
                                                 plan_h264_grid)
    from selkies_tpu.engine.types import CaptureSettings
    from selkies_tpu.ops import h264_encode as He
    from selkies_tpu.ops.bitpack import pack_slot_events

    print("backend:", jax.default_backend(), flush=True)
    s = CaptureSettings(capture_width=1920, capture_height=1080,
                        stripe_height=64, output_mode="h264", video_crf=28,
                        use_paint_over=False)
    g = plan_h264_grid(s)
    e_cap, w_cap, out_cap = h264_buffer_caps(g)
    R = g.n_stripes * g.rows_per_stripe          # MB rows
    M = g.mb_w
    print(f"grid {g.width}x{g.height} R={R} M={M} "
          f"e_cap={e_cap} w_cap={w_cap}", flush=True)

    rng = np.random.default_rng(0)
    frame = jnp.asarray(rng.integers(0, 256, (g.height, g.width, 3),
                                     dtype=np.uint8))

    # colorspace alone (cheap stages first: a killed run still reports)
    f_csc = jax.jit(He.rgb_to_yuv420)
    t_csc = t(f_csc, frame)
    print(f"rgb_to_yuv420: {t_csc*1e3:.1f} ms", flush=True)

    # pack_slot_events standalone on synthetic events:
    S = 9 + M * He.SLOTS_MB + 2
    pay_r = rng.integers(0, 2**16, (R, S), dtype=np.uint32)
    # realistic sparsity: ~25 active events per MB (73 bits/MB measured)
    active = rng.random((R, S)) < (25.0 * M / S)
    nb_r = np.where(active, rng.integers(1, 17, (R, S)), 0).astype(np.int32)
    payj, nbj = jnp.asarray(pay_r), jnp.asarray(nb_r)

    f_pack = jax.jit(lambda p, nbts: pack_slot_events(p, nbts, e_cap,
                                                      w_cap)[0])
    t_pack = t(f_pack, payj, nbj)
    print(f"pack_slot_events (R={R} x S={S}): {t_pack*1e3:.0f} ms",
          flush=True)

    # pack internals
    def front_pack(p, nbts):
        m_, s_ = p.shape
        act = nbts > 0
        slot_idx = jax.lax.broadcasted_iota(jnp.int32, (m_, s_), 1)
        order = jnp.argsort(jnp.where(act, slot_idx, s_ + slot_idx), axis=1)
        return jnp.take_along_axis(p, order, axis=1)
    t_sort = t(jax.jit(front_pack), payj, nbj)
    print(f"  argsort front-pack: {t_sort*1e3:.0f} ms", flush=True)

    def compact(p, nbts):
        m_, s_ = p.shape
        act = (nbts > 0)
        c_b = jnp.sum(act.astype(jnp.int32), axis=1)
        block_start_evt = jnp.cumsum(c_b) - c_b
        e_idx = jnp.arange(e_cap, dtype=jnp.int32)
        b = jnp.clip(jnp.searchsorted(block_start_evt, e_idx,
                                      side="right") - 1, 0, m_ - 1)
        slot = jnp.clip(e_idx - block_start_evt[b], 0, s_ - 1)
        return p[b, slot]
    t_comp = t(jax.jit(compact), payj, nbj)
    print(f"  searchsorted+gather compaction: {t_comp*1e3:.0f} ms",
          flush=True)

    def words(p, nbts):
        off_g = jnp.cumsum(nbts[0, :e_cap])
        pay_g = p[0, :e_cap]
        nb_g = nbts[0, :e_cap]
        w_idx = jnp.arange(w_cap, dtype=jnp.int32)
        ws = w_idx * 32
        s0 = jnp.clip(jnp.searchsorted(off_g, ws, side="right") - 1,
                      0, e_cap - 1)
        word = jnp.zeros((w_cap,), dtype=jnp.uint32)
        for k in range(33):
            e = jnp.clip(s0 + k, 0, e_cap - 1)
            word = word | jnp.where(nb_g[e] > 0, pay_g[e], 0)
        return word
    t_words = t(jax.jit(words), payj, nbj)
    print(f"  word materialisation (1 row x33 gathers): "
          f"{t_words*1e3:.0f} ms", flush=True)

    # full session steps LAST (the big compiles); encode() threads the
    # donated state correctly
    sess = H264EncoderSession(s)
    t_full = t(lambda f: sess.encode(f, force=True)["data"], frame, n=2)
    print(f"full I step (dispatch+block): {t_full*1e3:.0f} ms", flush=True)
    t_p = t(lambda f: sess.encode(f)["data"], frame, n=2)
    print(f"full P step (dispatch+block): {t_p*1e3:.0f} ms", flush=True)


if __name__ == "__main__":
    main()
