#!/usr/bin/env python
"""Pre-warm the persistent XLA compile cache for the default session
geometries.

The first compile of a 1080p H.264 program costs minutes (PERF.md); the
persistent cache (selkies_tpu/compile_cache.py) turns every LATER build
into seconds — but only if something paid the first compile. Run this at
image build (CPU backend) and at first boot / deploy on the TPU host
(each backend keys its own cache entries), so a user's first session
starts in seconds instead of staring at a black screen (VERDICT r3
weak 4; the reference ships pre-built codecs so it has no analogous
cold start).

    python tools/warm_cache.py --geometries 1920x1080,1280x720 \
        --codecs h264,jpeg

One process, sequential sessions: the TPU relay tolerates exactly one
JAX backend init at a time (PERF.md rules of engagement).
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--geometries", default="1920x1080,1280x720")
    ap.add_argument("--codecs", default="h264,jpeg")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (image builds)")
    args = ap.parse_args()

    if args.cpu:
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    from selkies_tpu.compile_cache import enable as enable_cache
    cache_dir = enable_cache(jax)
    print(f"warming {jax.default_backend()} -> {cache_dir}", flush=True)

    from selkies_tpu.engine.encoder import JpegEncoderSession
    from selkies_tpu.engine.h264_encoder import H264EncoderSession
    from selkies_tpu.engine.sources import SyntheticSource
    from selkies_tpu.engine.types import CaptureSettings

    failures = 0
    for geom in args.geometries.split(","):
        w, h = (int(v) for v in geom.lower().split("x"))
        for codec in args.codecs.split(","):
            t0 = time.monotonic()
            try:
                cs = CaptureSettings(
                    capture_width=w, capture_height=h,
                    output_mode=codec, video_crf=28, stripe_height=64,
                    use_damage_gating=True, use_paint_over=False)
                sess = (H264EncoderSession(cs) if codec == "h264"
                        else JpegEncoderSession(cs))
                src = SyntheticSource(sess.grid.width, sess.grid.height)
                # IDR + delta paths both hit distinct programs
                sess.finalize(sess.encode(src.get_frame(0), force=True),
                              force_all=True)
                try:
                    sess.finalize(sess.encode(src.get_frame(1)))
                except TypeError:
                    pass    # jpeg session has no distinct delta path
                print(f"  {codec} {w}x{h}: "
                      f"{time.monotonic() - t0:.1f}s", flush=True)
            except Exception as e:   # noqa: BLE001 — warm what we can
                failures += 1
                print(f"  {codec} {w}x{h}: FAILED "
                      f"({type(e).__name__}: {e})", flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
