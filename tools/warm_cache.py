#!/usr/bin/env python
"""Warm, pack, ship, and verify the persistent XLA compile cache.

The first compile of a 1080p H.264 program costs minutes (PERF.md); the
persistent cache (selkies_tpu/compile_cache.py) turns every LATER build
into seconds — but only if something paid the first compile. This tool
owns that lifecycle end to end (ISSUE 8):

    warm    compile the given geometry x codec matrix through real
            encoder sessions (image build / first boot); exits non-zero
            when ANY target fails so CI can gate on it
    pack    tar this host's fingerprint-keyed cache subtree + manifest
            into a distributable artifact (build once per microarch
            fingerprint in CI, ship to the fleet)
    unpack  extract an artifact into the local cache root — REFUSED on
            a fingerprint mismatch (exit 4: the cross-machine SIGILL
            hazard); jax-version mismatch refused unless --force-version
    verify  integrity + host-compatibility check without extracting

Every subcommand takes ``--json`` for a machine-readable result on
stdout (progress goes to stderr) — the CI artifact job and ``verify``
both consume it. Exit codes: 0 ok, 1 warm failure, 2 usage/IO,
3 artifact malformed, 4 fingerprint/jax-version refusal.

    python tools/warm_cache.py warm --geometries 1920x1080,1280x720 \\
        --codecs h264,jpeg --json
    python tools/warm_cache.py pack --out warm_cache.tar.gz
    python tools/warm_cache.py unpack warm_cache.tar.gz

One process, sequential sessions: the TPU relay tolerates exactly one
JAX backend init at a time (PERF.md rules of engagement). Bare
``python tools/warm_cache.py --geometries ...`` still works (legacy
spelling of ``warm``).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

EXIT_OK = 0
EXIT_WARM_FAILED = 1
EXIT_USAGE = 2
EXIT_MALFORMED = 3
EXIT_REFUSED = 4


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _emit(doc: dict, as_json: bool) -> None:
    if as_json:
        print(json.dumps(doc))


# ------------------------------------------------------------------- warm
def cmd_warm(args: argparse.Namespace) -> int:
    if args.cpu:
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    from selkies_tpu.compile_cache import enable as enable_cache
    from selkies_tpu.compile_cache import host_fingerprint
    cache_dir = enable_cache(jax)
    log(f"warming {jax.default_backend()} -> {cache_dir}")

    from selkies_tpu.engine.encoder import JpegEncoderSession
    from selkies_tpu.engine.h264_encoder import H264EncoderSession
    from selkies_tpu.engine.sources import SyntheticSource
    from selkies_tpu.engine.types import CaptureSettings

    results = []
    failures = 0
    for geom in args.geometries.split(","):
        w, h = (int(v) for v in geom.lower().split("x"))
        for codec in args.codecs.split(","):
            t0 = time.monotonic()
            entry = {"geometry": f"{w}x{h}", "codec": codec}
            try:
                cs = CaptureSettings(
                    capture_width=w, capture_height=h,
                    output_mode=codec, video_crf=28, stripe_height=64,
                    use_damage_gating=True, use_paint_over=False)
                sess = (H264EncoderSession(cs) if codec == "h264"
                        else JpegEncoderSession(cs))
                src = SyntheticSource(sess.grid.width, sess.grid.height)
                # IDR + delta paths both hit distinct programs
                sess.finalize(sess.encode(src.get_frame(0), force=True),
                              force_all=True)
                try:
                    sess.finalize(sess.encode(src.get_frame(1)))
                except TypeError:
                    pass    # jpeg session has no distinct delta path
                entry.update(ok=True,
                             seconds=round(time.monotonic() - t0, 1))
                log(f"  {codec} {w}x{h}: {entry['seconds']}s")
            except Exception as e:   # noqa: BLE001 — warm what we can
                failures += 1
                entry.update(ok=False,
                             seconds=round(time.monotonic() - t0, 1),
                             error=f"{type(e).__name__}: {e}"[:200])
                log(f"  {codec} {w}x{h}: FAILED ({entry['error']})")
            results.append(entry)
    _emit({"cmd": "warm", "ok": failures == 0,
           "backend": jax.default_backend(),
           "fingerprint": host_fingerprint(),
           "cache_dir": cache_dir, "failures": failures,
           "targets": results}, args.json)
    return EXIT_WARM_FAILED if failures else EXIT_OK


# ------------------------------------------------------- pack/unpack/verify
def _artifact_mod():
    from selkies_tpu.prewarm import artifact
    return artifact


def cmd_pack(args: argparse.Namespace) -> int:
    from selkies_tpu.compile_cache import host_fingerprint
    art = _artifact_mod()
    fp = host_fingerprint()
    out = args.out or f"warm_cache_{fp}.tar.gz"
    try:
        manifest = art.pack(out, cache_dir=args.cache_dir)
    except art.ArtifactError as e:
        log(f"pack failed: {e}")
        _emit({"cmd": "pack", "ok": False, "error": str(e)}, args.json)
        return EXIT_USAGE
    log(f"packed {manifest['files']} files "
        f"({manifest['bytes'] / 1e6:.1f} MB) for {fp} -> {out}")
    _emit({"cmd": "pack", "ok": True, "out": out,
           "manifest": {k: v for k, v in manifest.items()
                        if k != "entries"}}, args.json)
    return EXIT_OK


def _mismatch_result(cmd: str, e, as_json: bool) -> int:
    log(f"REFUSED: {e}")
    _emit({"cmd": cmd, "ok": False, "refused": True,
           "field": e.field, "error": str(e)}, as_json)
    return EXIT_REFUSED


def cmd_verify(args: argparse.Namespace) -> int:
    art = _artifact_mod()
    try:
        manifest = art.verify(args.artifact)
    except art.FingerprintMismatch as e:
        return _mismatch_result("verify", e, args.json)
    except art.ArtifactError as e:
        log(f"verify failed: {e}")
        _emit({"cmd": "verify", "ok": False, "error": str(e)},
              args.json)
        return EXIT_MALFORMED
    log(f"ok: {manifest['files']} files for "
        f"{manifest['fingerprint']} (jax {manifest['jax_version']})")
    _emit({"cmd": "verify", "ok": True,
           "manifest": {k: v for k, v in manifest.items()
                        if k != "entries"}}, args.json)
    return EXIT_OK


def cmd_unpack(args: argparse.Namespace) -> int:
    art = _artifact_mod()
    try:
        res = art.unpack(args.artifact, root=args.root,
                         force_version=args.force_version)
    except art.FingerprintMismatch as e:
        return _mismatch_result("unpack", e, args.json)
    except art.ArtifactError as e:
        log(f"unpack failed: {e}")
        _emit({"cmd": "unpack", "ok": False, "error": str(e)},
              args.json)
        return EXIT_MALFORMED
    log(f"unpacked {res['files']} files into {res['dir']}")
    _emit({"cmd": "unpack", "ok": True, **res}, args.json)
    return EXIT_OK


# -------------------------------------------------------------------- main
def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # legacy spelling: bare flags mean `warm`
    if not argv or argv[0].startswith("-"):
        argv.insert(0, "warm")
    p = argparse.ArgumentParser(prog="warm_cache.py",
                                description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)

    pw = sub.add_parser("warm", help="compile the geometry x codec matrix")
    pw.add_argument("--geometries", default="1920x1080,1280x720")
    pw.add_argument("--codecs", default="h264,jpeg")
    pw.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (image builds)")
    pw.add_argument("--json", action="store_true")
    pw.set_defaults(fn=cmd_warm)

    pp = sub.add_parser("pack", help="tar this host's cache + manifest")
    pp.add_argument("--out", default="",
                    help="output path (default warm_cache_<fp>.tar.gz)")
    pp.add_argument("--cache-dir", default=None,
                    help="cache subtree to pack (default: this host's "
                         "fingerprint dir under the cache root)")
    pp.add_argument("--json", action="store_true")
    pp.set_defaults(fn=cmd_pack)

    pv = sub.add_parser("verify", help="check integrity + host match")
    pv.add_argument("artifact")
    pv.add_argument("--json", action="store_true")
    pv.set_defaults(fn=cmd_verify)

    pu = sub.add_parser("unpack", help="extract into the local cache "
                                       "root (fingerprint-checked)")
    pu.add_argument("artifact")
    pu.add_argument("--root", default=None,
                    help="cache root to extract under (default: the "
                         "configured JAX cache root)")
    pu.add_argument("--force-version", action="store_true",
                    help="tolerate a jax-version mismatch (fingerprint "
                         "mismatches are never overridable)")
    pu.add_argument("--json", action="store_true")
    pu.set_defaults(fn=cmd_unpack)

    try:
        args = p.parse_args(argv)
    except SystemExit as e:
        return EXIT_USAGE if e.code not in (0, None) else 0
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
